"""Inference stack tests: predictor serving + reference byte formats.

The reference-format roundtrip is the SURVEY hard-part #6 acceptance: a
model written in the reference's `__model__` protobuf + SerializeToStream
params must load and serve here (and our artifacts must parse back).
"""
import os
import struct

import numpy as np
import pytest

import paddle_tpu as fluid


def _train_small(tmp):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 12
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        h = fluid.layers.fc(x, size=16, act='relu')
        y = fluid.layers.fc(h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        xs = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        want, = exe.run(main_p, feed={'x': xs}, fetch_list=[y])
    return main_p, startup_p, scope, x, y, xs, want, exe


def test_reference_format_roundtrip(tmp_path):
    """Write the reference byte formats, read them back, get identical
    outputs."""
    d = str(tmp_path / 'ref_model')
    main_p, startup_p, scope, x, y, xs, want, exe = _train_small(d)
    from paddle_tpu.inference import (save_reference_inference_model,
                                      load_reference_inference_model)
    with fluid.scope_guard(scope):
        save_reference_inference_model(d, ['x'], [y], exe,
                                       main_program=main_p)
    # the __model__ must be protobuf, not our JSON
    with open(os.path.join(d, '__model__'), 'rb') as f:
        head = f.read(1)
    assert head != b'{'
    # param files carry the tensor-stream magic (u32 version 0)
    pfiles = [f for f in os.listdir(d) if f != '__model__']
    assert pfiles
    with open(os.path.join(d, pfiles[0]), 'rb') as f:
        assert struct.unpack('<I', f.read(4))[0] == 0

    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_reference_inference_model(d, exe,
                                                              scope=scope2)
        assert feeds == ['x']
        got, = exe.run(prog, feed={'x': xs},
                       fetch_list=[fetches[0].name])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_reference_combined_params(tmp_path):
    d = str(tmp_path / 'ref_combined')
    main_p, startup_p, scope, x, y, xs, want, exe = _train_small(d)
    from paddle_tpu.inference import (save_reference_inference_model,
                                      load_reference_inference_model)
    with fluid.scope_guard(scope):
        save_reference_inference_model(d, ['x'], [y], exe,
                                       main_program=main_p,
                                       params_filename='__params__')
    assert set(os.listdir(d)) == {'__model__', '__params__'}
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_reference_inference_model(
            d, exe, params_filename='__params__', scope=scope2)
        got, = exe.run(prog, feed={'x': xs},
                       fetch_list=[fetches[0].name])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_serves_both_formats(tmp_path):
    dref = str(tmp_path / 'm_ref')
    dnat = str(tmp_path / 'm_nat')
    main_p, startup_p, scope, x, y, xs, want, exe = _train_small(dref)
    from paddle_tpu.inference import (save_reference_inference_model,
                                      Config, create_predictor)
    with fluid.scope_guard(scope):
        save_reference_inference_model(dref, ['x'], [y], exe,
                                       main_program=main_p)
        fluid.save_inference_model(dnat, ['x'], [y], exe,
                                   main_program=main_p)
    for d in (dref, dnat):
        cfg = Config(model_dir=d)
        cfg.disable_gpu()
        pred = create_predictor(cfg).warmup([xs])
        assert pred.get_input_names() == ['x']
        out, = pred.run([xs])
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
        # clone shares weights and serves identically
        out2, = pred.clone().run({'x': xs})
        np.testing.assert_allclose(out2, want, rtol=1e-6)


def test_multi_input_feed_order_preserved(tmp_path):
    """Feed ops are prepended in reverse block order; the 'col' attr is the
    authoritative ordering and must drive get_input_names."""
    d = str(tmp_path / 'two_inputs')
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        a = fluid.layers.data(name='a', shape=[2], dtype='float32')
        b = fluid.layers.data(name='b', shape=[2], dtype='float32')
        y = a * 2.0 + b
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    from paddle_tpu.inference import (save_reference_inference_model,
                                      load_reference_inference_model,
                                      Config, create_predictor)
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        save_reference_inference_model(d, ['a', 'b'], [y], exe,
                                       main_program=main_p)
        prog, feeds, fetches = load_reference_inference_model(d, exe,
                                                              scope=scope)
    assert feeds == ['a', 'b']
    av = np.array([[1.0, 2.0]], np.float32)
    bv = np.array([[10.0, 20.0]], np.float32)
    pred = create_predictor(Config(model_dir=d))
    out, = pred.run([av, bv])
    np.testing.assert_allclose(out, av * 2 + bv, rtol=1e-6)


def test_dtype_enum_attrs_roundtrip(tmp_path):
    """dtype-valued attrs (cast out_dtype, fill_constant dtype) travel as
    VarType enum INTS in the reference format and must run after reload."""
    d = str(tmp_path / 'dtype_model')
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        xi = fluid.layers.cast(x, 'int32')
        y = fluid.layers.cast(xi, 'float32') + fluid.layers.fill_constant(
            shape=[1], dtype='float32', value=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    from paddle_tpu.inference import (save_reference_inference_model,
                                      load_reference_inference_model)
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        save_reference_inference_model(d, ['x'], [y], exe,
                                       main_program=main_p)
        prog, feeds, fetches = load_reference_inference_model(d, exe,
                                                              scope=scope)
        # the reloaded cast op carries the enum int, not our string
        casts = [op for op in prog.global_block().ops if op.type == 'cast']
        assert casts and isinstance(casts[0].attrs['out_dtype'], int)
        xs = np.array([[1.7, -2.3, 0.5, 3.9]], np.float32)
        got, = exe.run(prog, feed={'x': xs},
                       fetch_list=[fetches[0].name])
    np.testing.assert_allclose(got, np.trunc(xs) + 2.0, rtol=1e-6)


def test_lod_tensor_stream_roundtrip(tmp_path):
    from paddle_tpu.inference.ref_format import (write_tensor_stream,
                                                 read_tensor_stream)
    arr = np.random.RandomState(1).randn(6, 3).astype(np.float32)
    lod = [np.array([0, 2, 6], np.int64)]
    p = tmp_path / 't.bin'
    with open(p, 'wb') as f:
        write_tensor_stream(f, arr, lod)
    with open(p, 'rb') as f:
        arr2, lod2 = read_tensor_stream(f)
    np.testing.assert_allclose(arr2, arr)
    np.testing.assert_array_equal(lod2[0], lod[0])
    # int64 tensors survive too
    ids = np.arange(10, dtype=np.int64).reshape(5, 2)
    with open(p, 'wb') as f:
        write_tensor_stream(f, ids, None, with_lod=False)
    with open(p, 'rb') as f:
        ids2, _ = read_tensor_stream(f, has_lod=False)
    np.testing.assert_array_equal(ids2, ids)
