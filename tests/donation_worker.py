"""Subprocess worker for test_dataflow.py and donation_smoke.py: one
fresh-process train run through the persistent compile cache, reporting
whether certified state donation was active and whether it actually
eliminated the per-step state copy.

    python donation_worker.py CACHE_DIR OUT.npz

Env: PTPU_COMPILE_CACHE=0 turns the cache off (the uncached reference);
PTPU_WARM_DONATION=0 keeps the cache but forces the undonated round-8
behavior (the copy-tax control arm); PTPU_DONATION_WORKER_RESEED=1
round-trips the scope state through HOST numpy between the two groups —
the restored-checkpoint shape of the zero-copy hazard (a reloaded
donating executable must never scribble over host-backed buffers; the
executor copies such leaves to XLA-owned memory at the boundary), so
the fetches must stay byte-identical to the un-reseeded run.

Runs startup + two K=3 run_steps groups on a deterministic fc net,
saves every fetch and the final persistable state to OUT.npz, and
prints one DONATION_STATS JSON line:

  cert_safe       the dataflow certifier's verdict for this program
  exec_hits/misses/xla_compiles_net   compile-cache counters
  donated_entries how many on-disk entries record donated=True
  old_deleted     state buffers jax marked deleted after dispatch 2
                  (donation executed — the copy is gone)
  aliased_state   new state buffers that landed on the OLD buffer's
                  address (XLA aliased the update in place)
  state_total     donated state var count
"""
import json
import os
import sys


def main():
    cache_dir, out_path = sys.argv[1], sys.argv[2]
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['PTPU_PLATFORM'] = 'cpu'
    os.environ.setdefault('PTPU_COMPILE_CACHE', '1')
    os.environ['PTPU_COMPILE_CACHE_DIR'] = cache_dir
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import glob
    import time
    import warnings

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core import compile_cache as cc

    t0 = time.perf_counter()

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_p, startup), unique_name.guard():
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, size=8, act='relu')
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)

    rng = np.random.RandomState(0)
    groups = [{'x': rng.randn(3, 4, 6).astype(np.float32),
               'y': rng.randn(3, 4, 1).astype(np.float32)}
              for _ in range(2)]

    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    save = {}
    with fluid.scope_guard(scope), warnings.catch_warnings():
        # XLA backends without usable donation warn per call; the probe
        # below MEASURES donation instead of trusting the absence of the
        # warning, so keep the output parseable
        warnings.filterwarnings(
            'ignore', message='Some donated buffers were not usable')
        exe.run(startup)
        out, = exe.run_steps(main_p, feed=groups[0], fetch_list=[loss],
                             fetch_policy='stack')
        save['g0'] = np.asarray(out)

        if os.environ.get('PTPU_DONATION_WORKER_RESEED') == '1':
            # the restore shape of the zero-copy hazard: state re-enters
            # the scope as host numpy; the next (possibly reloaded,
            # donating) dispatch must copy it to owned buffers, never
            # donate it in place
            for n, v in list(scope._vars.items()):
                if v is not None:
                    scope.set(n, np.array(np.asarray(v), copy=True))

        # probe dispatch 2: donation shows as the old buffers dying (and
        # usually the new state landing at the same addresses)
        import jax
        old = {}
        for n, v in scope._vars.items():
            if isinstance(v, jax.Array) and not v.is_deleted():
                try:
                    old[n] = (v, v.unsafe_buffer_pointer())
                except Exception:
                    old[n] = (v, None)
        out, = exe.run_steps(main_p, feed=groups[1], fetch_list=[loss],
                             fetch_policy='stack')
        save['g1'] = np.asarray(out)

        old_deleted = sum(1 for v, _ in old.values() if v.is_deleted())
        aliased = 0
        for n, (v, ptr) in old.items():
            nv = scope.get(n)
            if ptr is None or not isinstance(nv, jax.Array):
                continue
            try:
                if nv.unsafe_buffer_pointer() == ptr:
                    aliased += 1
            except Exception:
                pass
        for n, v in sorted(scope._vars.items()):
            if v is not None:
                save['state_%s' % n] = np.asarray(v)
    np.savez(out_path, **save)

    cert = exe._donation_certs.get(main_p._uid)
    donated_entries = 0
    for p in glob.glob(os.path.join(cache_dir, 'entries', '*.json')):
        try:
            with open(p) as f:
                donated_entries += bool(json.load(f).get('donated'))
        except (OSError, ValueError):
            pass
    s = cc.stats()
    print('DONATION_STATS %s' % json.dumps({
        'cert_safe': bool(cert.safe) if cert is not None else None,
        'cert_reasons': list(cert.reasons) if cert is not None else [],
        'exec_hits': s['exec_hits'], 'misses': s['misses'],
        'xla_compiles_net': s['xla_compiles_net'],
        'donated_entries': donated_entries,
        'old_deleted': old_deleted, 'aliased_state': aliased,
        'state_total': len(old),
        'wall_s': round(time.perf_counter() - t0, 3)}))
    print('DONATION_OK')


if __name__ == '__main__':
    main()
