"""Imperative (proto-dygraph) mode + quantization-aware training."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_imperative_fc_trains():
    """The reference's proto-dygraph test shape: layers compose eagerly,
    loss.backward() fills parameter gradients, manual SGD learns."""
    from paddle_tpu import imperative
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 8).astype(np.float32)
    w_true = rng.randn(8, 1).astype(np.float32)
    ys = xs @ w_true

    with imperative.guard():
        fc1 = imperative.FC(size=16, act='relu')
        fc2 = imperative.FC(size=1)
        losses = []
        for step in range(30):
            x = imperative.to_variable(xs)
            y = imperative.to_variable(ys)
            pred = fc2(fc1(x))
            diff = pred - y
            loss_v = (diff * diff)
            from paddle_tpu.imperative.base import apply
            loss = apply(lambda d: d.mean(), loss_v)
            loss.backward()
            for lyr in (fc1, fc2):
                lyr.apply_gradients(0.05)
                lyr.clear_gradients()
            losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.3
    assert fc1.weight.gradient() is None  # cleared


def test_imperative_conv_pool_forward_backward():
    from paddle_tpu import imperative
    with imperative.guard():
        conv = imperative.Conv2D(num_channels=1, num_filters=2,
                                 filter_size=3, padding=1)
        pool = imperative.Pool2D(pool_size=2, pool_type='max',
                                 pool_stride=2)
        x = imperative.to_variable(
            np.random.RandomState(1).randn(2, 1, 8, 8).astype(np.float32))
        out = pool(conv(x))
        assert out.shape == (2, 2, 4, 4)
        from paddle_tpu.imperative.base import apply
        loss = apply(lambda v: v.sum(), out)
        loss.backward()
        g = conv.weight.gradient()
        assert g is not None and g.shape == (2, 1, 3, 3)
        assert np.abs(g).sum() > 0


def test_imperative_pool_ceil_mode_matches_graph_lowering():
    """Pool2D(ceil_mode=True) passes the attr through to the same padding
    discipline as the graph lowering (ops/nn_ops.py ceil_mode_pads) —
    VERDICT r5 item 9 deleted the NotImplementedError."""
    import paddle_tpu as fluid
    from paddle_tpu import imperative
    # 6x6 with k=3 s=2 leaves remainder 1, so ceil GENUINELY differs from
    # floor: ceil((6-3)/2)+1 = 3 vs floor's 2 — a 7x7 input would divide
    # evenly and make this parity check vacuous
    x = np.random.RandomState(3).randn(2, 1, 6, 6).astype(np.float32)

    for ptype, exclusive in [('max', True), ('avg', True), ('avg', False)]:
        with imperative.guard():
            pool = imperative.Pool2D(pool_size=3, pool_type=ptype,
                                     pool_stride=2, ceil_mode=True,
                                     exclusive=exclusive)
            dy = pool(imperative.to_variable(x)).numpy()
        assert dy.shape == (2, 1, 3, 3), dy.shape

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            data = fluid.layers.data(name='x', shape=[1, 6, 6],
                                     dtype='float32')
            out = fluid.layers.pool2d(data, pool_size=3, pool_type=ptype,
                                      pool_stride=2, ceil_mode=True,
                                      exclusive=exclusive)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        st, = exe.run(main, feed={'x': x}, fetch_list=[out])
        np.testing.assert_allclose(dy, st, rtol=1e-6, atol=1e-6)


def test_pool_ceil_mode_all_padding_window_is_finite():
    """stride > kernel with ceil_mode can place a window ENTIRELY in the
    high-side ceil padding: exclusive avg counts 0 real elements there
    and must clamp (0, not NaN) — graph and dygraph agree."""
    import paddle_tpu as fluid
    from paddle_tpu import imperative
    x = np.random.RandomState(5).randn(1, 1, 7, 7).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name='x', shape=[1, 7, 7],
                                 dtype='float32')
        out = fluid.layers.pool2d(data, pool_size=2, pool_type='avg',
                                  pool_stride=4, ceil_mode=True,
                                  exclusive=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    st, = exe.run(main, feed={'x': x}, fetch_list=[out])
    assert np.isfinite(st).all(), st
    with imperative.guard():
        pool = imperative.Pool2D(pool_size=2, pool_type='avg',
                                 pool_stride=4, ceil_mode=True,
                                 exclusive=True)
        dy = pool(imperative.to_variable(x)).numpy()
    np.testing.assert_allclose(dy, st, rtol=1e-6, atol=1e-6)


def test_imperative_pool_ceil_mode_backward():
    from paddle_tpu import imperative
    from paddle_tpu.imperative.base import apply
    with imperative.guard():
        conv = imperative.Conv2D(num_channels=1, num_filters=2,
                                 filter_size=3, padding=1)
        pool = imperative.Pool2D(pool_size=2, pool_type='avg',
                                 pool_stride=2, ceil_mode=True)
        x = imperative.to_variable(
            np.random.RandomState(4).randn(2, 1, 5, 5).astype(np.float32))
        out = pool(conv(x))
        assert out.shape == (2, 2, 3, 3)  # 5 -> ceil(3/2)+1 = 3
        loss = apply(lambda v: v.sum(), out)
        loss.backward()
        g = conv.weight.gradient()
        assert g is not None and np.abs(g).sum() > 0


def test_imperative_grad_accumulates_shared_param():
    from paddle_tpu import imperative
    from paddle_tpu.imperative.base import apply, to_variable
    with imperative.guard():
        w = to_variable(np.ones(3, np.float32))
        a = apply(lambda v: (v * 2.0).sum(), w)
        b = apply(lambda v: (v * 3.0).sum(), w)
        s = a + b
        s.backward()
        np.testing.assert_allclose(w.gradient(), np.full(3, 5.0), rtol=1e-6)


def test_quantize_transpiler_trains_and_quantizes():
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 5
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=16, act='relu')
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.optimizer.Adam(5e-2).minimize(loss)
    t = fluid.contrib.quantize.QuantizeTranspiler(weight_bits=8,
                                                  activation_bits=8)
    t.training_transpile(main_p, startup_p)
    ops = [op.type for op in main_p.global_block().ops]
    assert 'fake_quantize_abs_max' in ops
    # every mul's inputs are now quantized vars
    for op in main_p.global_block().ops:
        if op.type == 'mul' and not op.attrs.get('op_role', 0):
            assert all('.quantized.' in n for n in op.inputs['X'])

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(2)
    xs = rng.randn(32, 8).astype(np.float32)
    labs = rng.randint(0, 4, (32, 1))
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(25):
            l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
    # quantization-aware training still converges (STE gradients)
    assert losses[-1] < losses[0] * 0.6


def test_quantize_transpiler_range_abs_max():
    """range_abs_max activations (ref quantize_transpiler.py:105): the
    scale comes from a sliding window of per-step abs-max stats held as
    in-graph persistable state (Scales[window] + Iter), while weights
    keep plain abs_max — both quant types live in one program and QAT
    still converges through the STE."""
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 5
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=16, act='relu')
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.optimizer.Adam(5e-2).minimize(loss)
    t = fluid.contrib.quantize.QuantizeTranspiler(
        activation_quantize_type='range_abs_max', window_size=4)
    t.training_transpile(main_p, startup_p)
    ops = [op for op in main_p.global_block().ops]
    range_ops = [op for op in ops
                 if op.type == 'fake_quantize_range_abs_max']
    assert range_ops, 'no range_abs_max op inserted for activations'
    # weights still quantize via plain abs_max
    assert any(op.type == 'fake_quantize_abs_max' for op in ops)
    # the window state threads through under the same names
    for op in range_ops:
        assert op.inputs['Scales'] == op.outputs['OutScales']
        assert op.inputs['Iter'] == op.outputs['OutIter']
        assert int(op.attrs['window_size']) == 4

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(2)
    xs = rng.randn(32, 8).astype(np.float32)
    labs = rng.randint(0, 4, (32, 1))
    steps = 3
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(steps):
            l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        scales_name = range_ops[0].inputs['Scales'][0]
        iter_name = range_ops[0].inputs['Iter'][0]
        window = np.asarray(scope.get(scales_name))
        it = np.asarray(scope.get(iter_name))
        # the counter advanced once per step; 3 of 4 slots are filled
        assert int(it.reshape(-1)[0]) == steps
        assert window.shape == (4,)
        assert np.count_nonzero(window) == steps
        # 'x' is fed verbatim every step: its per-step abs-max stats are
        # identical, and the published scale is the window max
        x_scale = [op for op in range_ops if op.inputs['X'] == ['x']]
        if x_scale:
            w = np.asarray(scope.get(x_scale[0].inputs['Scales'][0]))
            assert w.max() == pytest.approx(np.abs(xs).max(), rel=1e-5)
        # freeze flips the window to read-only (is_test)
        t.freeze_program(main_p)
        exe.run(main_p, feed={'x': xs, 'lab': labs}, fetch_list=[loss])
        it2 = np.asarray(scope.get(iter_name))
        assert int(it2.reshape(-1)[0]) == steps   # frozen: no advance
    assert losses[-1] < losses[0]


def test_fake_quant_grid():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    helper_out = fluid.default_main_program().global_block().create_var(
        name='q', dtype='float32', stop_gradient=False)
    scale_out = fluid.default_main_program().global_block().create_var(
        name='qs', dtype='float32', stop_gradient=True)
    fluid.default_main_program().global_block().append_op(
        type='fake_quantize_abs_max', inputs={'X': ['x']},
        outputs={'Out': ['q'], 'OutScale': ['qs']},
        attrs={'bit_length': 8}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.array([[0.1, -0.5, 0.25, 1.0]], np.float32)
    q, s = exe.run(feed={'x': xs}, fetch_list=['q', 'qs'])
    assert float(np.asarray(s)[0]) == pytest.approx(1.0)
    # values land on the 127-step grid
    np.testing.assert_allclose(np.asarray(q) * 127,
                               np.round(np.asarray(q) * 127), atol=1e-4)
    np.testing.assert_allclose(q, xs, atol=1.0 / 127)


def test_pylayer_custom_backward_honored():
    from paddle_tpu import imperative
    from paddle_tpu.imperative import PyLayer

    class TripleGrad(PyLayer):
        @staticmethod
        def forward(x):
            return x * 1.0

        @staticmethod
        def backward(x, dout):
            return dout * 3.0   # surrogate gradient

    with imperative.guard():
        w = imperative.to_variable(np.ones(2, np.float32))
        from paddle_tpu.imperative.base import apply
        out = TripleGrad.apply(w)
        loss = apply(lambda v: v.sum(), out)
        loss.backward()
    np.testing.assert_allclose(w.gradient(), np.full(2, 3.0), rtol=1e-6)


def test_pool2d_exclusive_avg_padding():
    from paddle_tpu import imperative
    with imperative.guard():
        pool = imperative.Pool2D(pool_size=2, pool_type='avg',
                                 pool_stride=2, pool_padding=1)
        x = imperative.to_variable(np.ones((1, 1, 2, 2), np.float32))
        out = pool(x)
    # exclusive=True: padded border windows average only valid elements
    np.testing.assert_allclose(out.numpy(), np.ones((1, 1, 2, 2)),
                               rtol=1e-6)


def test_dlpack_bridge():
    import jax.numpy as jnp
    import paddle_tpu as fluid
    torch = pytest.importorskip('torch')
    x = jnp.asarray(np.arange(6, dtype=np.float32))
    t = torch.from_dlpack(fluid.core.to_dlpack(x))
    np.testing.assert_allclose(t.numpy(), np.arange(6, dtype=np.float32))
    back = fluid.core.from_dlpack(torch.arange(4).float())
    np.testing.assert_allclose(np.asarray(back), np.arange(4))
