"""Elastic pod resizing (ISSUE 14): restore a pod checkpoint onto a
DIFFERENT topology.

Units drive the three layers separately: journal re-striding
(reader/elastic.read_journal_state + merge, reader/sharded.
restride_journal), the shared state-sharding rule + divisibility gate
(parallel/reshard.py), and PodCheckpointManager's topology-change
restore (duck-typed pods, no jax.distributed needed). The same-shape
fast path is PINNED: zero resharding programs, byte-identical params.
The subprocess test runs the real thing — a 2-process composed-mesh
run with a sharded data journal killed at a committed boundary and
resumed on ONE host: loss trajectory within float-accumulation
tolerance of the uninterrupted 2-host reference, per-step record sets
identical, every epoch's sample accounting exactly-once.
"""
import importlib.util
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

from paddle_tpu.core.checkpoint import (
    PodCheckpointManager, pod_verify, read_heartbeats)
from paddle_tpu.core.scope import Scope
from paddle_tpu.parallel.reshard import (
    ReshardError, check_reshardable, nearest_valid_sizes,
    reshard_stats, reset_reshard_stats, state_shardings_for)
from paddle_tpu.reader.elastic import (
    TaskService, merge_journal_states, read_journal_state)
from paddle_tpu.reader.sharded import restride_journal, shard_assignment

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chaos():
    spec = importlib.util.spec_from_file_location(
        'ptpu_chaos_e', os.path.join(REPO, 'tools', 'chaos.py'))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# journal replay / merge / re-stride
# ---------------------------------------------------------------------------
def _write_journal(path, events):
    with open(path, 'w') as f:
        for ev in events:
            f.write(json.dumps(ev) + '\n')


def test_read_journal_state_replays_and_respects_limit(tmp_path):
    p = str(tmp_path / 'j.jsonl')
    evs = [{'event': 'epoch', 'epoch': 2},
           {'event': 'done', 'task': 'a'},
           {'event': 'progress', 'task': 'b', 'count': 3},
           {'event': 'meta', 'key': 'bs', 'value': 16},
           {'event': 'done', 'task': 'b'}]
    _write_journal(p, evs)
    st = read_journal_state(p)
    assert st['epoch'] == 2 and st['done'] == {'a', 'b'}
    assert st['progress'] == {} and st['meta'] == {'bs': 16}
    # limit = everything before the final done: b is still in progress,
    # exactly the state a checkpoint at that position described
    limit = sum(len(json.dumps(e)) + 1 for e in evs[:-1])
    st = read_journal_state(p, limit=limit)
    assert st['done'] == {'a'} and st['progress'] == {'b': 3}
    # a limit landing mid-line drops the torn record, like crash recovery
    st = read_journal_state(p, limit=limit + 3)
    assert st['done'] == {'a'}
    # an epoch event resets everything before it
    _write_journal(p, evs + [{'event': 'epoch', 'epoch': 3}])
    st = read_journal_state(p)
    assert st['epoch'] == 3 and not st['done'] and not st['progress']


def test_merge_journal_states_epoch_and_meta_guards():
    a = read_journal_state(None)
    b = read_journal_state(None)
    a['done'].add('t0')
    b['progress']['t1'] = 4
    merged = merge_journal_states([a, b])
    assert merged['done'] == {'t0'} and merged['progress'] == {'t1': 4}
    # done wins over progress (lease-board reclaim overlap)
    b['progress']['t0'] = 2
    assert merge_journal_states([a, b])['progress'] == {'t1': 4}
    b['epoch'] = 1
    with pytest.raises(ValueError, match='disagree on the epoch'):
        merge_journal_states([a, b])
    b['epoch'] = 0
    a['meta']['bs'] = 16
    b['meta']['bs'] = 32
    with pytest.raises(ValueError, match="meta 'bs'"):
        merge_journal_states([a, b])


def test_restride_journal_maps_old_stride_onto_new(tmp_path):
    """4 old hosts' journals at a synchronized boundary re-stride onto 2
    and onto 8 shards: done chunks stay done exactly once, the one
    mid-chunk progress position survives, nothing is lost."""
    tasks = ['c%02d' % i for i in range(16)]
    # old pod: 4 hosts, host r owns tasks r::4; the pod consumed the
    # first 8 chunks (2 per host) and host 1 is 5 records into c05
    olds = []
    for r in range(4):
        p = str(tmp_path / ('old-%d.jsonl' % r))
        evs = [{'event': 'epoch', 'epoch': 1}]
        mine = tasks[r::4]
        evs += [{'event': 'done', 'task': t} for t in mine[:2]]
        if r == 1:
            evs.append({'event': 'progress', 'task': 'c09', 'count': 5})
        _write_journal(p, evs)
        olds.append((p, None))
    consumed = {t for r in range(4) for t in tasks[r::4][:2]}
    for new_n in (2, 8):
        seen_done, seen_prog = set(), {}
        for shard in range(new_n):
            out = str(tmp_path / ('new-%d-of-%d.jsonl' % (shard, new_n)))
            counts = restride_journal(olds, None, new_n, shard, out,
                                      tasks=tasks)
            st = read_journal_state(out)
            assert st['epoch'] == 1
            assert counts['total'] == len(tasks) // new_n
            mine = set(shard_assignment(tasks, new_n, shard))
            assert st['done'] == consumed & mine
            assert set(st['progress']) == {'c09'} & mine
            assert not (seen_done & st['done'])    # disjoint cover
            seen_done |= st['done']
            seen_prog.update(st['progress'])
        assert seen_done == consumed               # nothing lost
        assert seen_prog == {'c09': 5}
        # a fresh TaskService over the new stride dispatches exactly the
        # unconsumed remainder, resuming c09 at its delivered position
        svc = TaskService(
            shard_assignment(tasks, new_n, 0),
            journal_path=str(tmp_path / ('new-0-of-%d.jsonl' % new_n)))
        todo = {}
        while True:
            lease = svc.get_task()
            if lease is None:
                break
            todo[lease[0]] = lease[2]
        svc.close()
        expect = {t: (5 if t == 'c09' else 0)
                  for t in shard_assignment(tasks, new_n, 0)
                  if t not in consumed}
        assert todo == expect


def test_restride_journal_guards(tmp_path):
    tasks = ['a', 'b']
    good = str(tmp_path / 'good.jsonl')
    _write_journal(good, [{'event': 'done', 'task': 'a'}])
    out = str(tmp_path / 'out.jsonl')
    with pytest.raises(ValueError, match='missing'):
        restride_journal([(good, None), (str(tmp_path / 'nope'), None)],
                         None, 1, 0, out, tasks=tasks)
    with pytest.raises(ValueError, match='missing'):
        restride_journal([(good, None), None], None, 1, 0, out,
                         tasks=tasks)
    bad = str(tmp_path / 'bad.jsonl')
    _write_journal(bad, [{'event': 'done', 'task': 'zz'}])
    with pytest.raises(ValueError, match='file set does not'):
        restride_journal([(good, None), (bad, None)], None, 1, 0, out,
                         tasks=tasks)
    # atomic: the failed attempts left no half-written journal behind
    assert not os.path.exists(out)


# ---------------------------------------------------------------------------
# the shared sharding rule + the divisibility gate
# ---------------------------------------------------------------------------
def test_nearest_valid_sizes():
    assert nearest_valid_sizes(32, 3) == (2, 4)
    assert nearest_valid_sizes(32, 8) == (8, 8)
    assert nearest_valid_sizes(5, 2) == (1, 5)
    assert nearest_valid_sizes(7, 9) == (7, 7)


def test_check_reshardable_names_param_and_nearest_counts():
    from paddle_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(num_devices=3, axes={'dp': 3})
    with pytest.raises(ReshardError) as e:
        check_reshardable({'fc2_w': (32, 5)}, {'fc2_w': ('dp', None)},
                          mesh, old_num_hosts=4, new_num_hosts=3)
    msg = str(e.value)
    assert "'fc2_w'" in msg and 'not divisible' in msg
    assert '2 (shrink) / 4 (grow)' in msg
    assert '4-host checkpoint onto 3 host' in msg
    # divisible shapes pass silently
    check_reshardable({'fc2_w': (33, 5)}, {'fc2_w': ('dp', None)}, mesh)


def test_state_shardings_for_slot_inheritance():
    """The factored rule (parallel/reshard.py) behaves exactly like the
    executor's dispatch-time assignment: annotated params shard,
    same-shape prefix-named optimizer slots inherit, everything else
    replicates."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel import shard_parameter
    from paddle_tpu.parallel.mesh import make_mesh
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        y = fluid.layers.fc(x, size=32,
                            param_attr=fluid.ParamAttr(name='fcw'))
        loss = fluid.layers.mean(y)
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)
    shard_parameter(main_p.global_block().var('fcw'), (None, 'mp'))
    mesh = make_mesh(num_devices=4, axes={'dp': 2, 'mp': 2})
    names = sorted(v.name for v in main_p.list_vars() if v.persistable)
    shardings, specs = state_shardings_for(main_p, mesh, names)
    slot = [n for n in names if n.startswith('fcw_velocity')]
    assert slot, names
    assert specs['fcw'] == (None, 'mp')
    assert specs[slot[0]] == (None, 'mp')       # inherited
    rep = [n for n in names if n not in specs]
    assert rep and all(shardings[n].spec == () for n in rep)


# ---------------------------------------------------------------------------
# topology-change restore (duck-typed pods, as in test_pod_ft)
# ---------------------------------------------------------------------------
class FakeVar(object):
    def __init__(self, name):
        self.name, self.persistable = name, True


class FakeProgram(object):
    _uid = 5150
    random_seed = 7

    def __init__(self, names=('w', 'b')):
        self._names = names

    def list_vars(self):
        return [FakeVar(n) for n in self._names]


class _Dev(object):
    def __init__(self, pi):
        self.process_index = pi


class _Sharding(object):
    def __init__(self, imap):
        self._imap = imap

    def devices_indices_map(self, shape):
        return self._imap


class _Shard(object):
    def __init__(self, idx, data):
        self.index, self.data = idx, data


class FakeGlobal(object):
    is_fully_addressable = False

    def __init__(self, shape, shards, imap):
        self.shape = shape
        self.addressable_shards = shards
        self.sharding = _Sharding(imap)


FULL_W = np.arange(16, dtype=np.float32).reshape(4, 4)


def scope_for(rank):
    sc = Scope()
    top = _Shard((slice(0, 2), slice(None)), FULL_W[:2])
    bot = _Shard((slice(2, 4), slice(None)), FULL_W[2:])
    imap = {_Dev(0): (slice(0, 2), slice(None)),
            _Dev(1): (slice(2, 4), slice(None))}
    sc.set('w', FakeGlobal((4, 4), [top] if rank == 0 else [bot], imap))
    sc.set('b', np.full((3,), 1.5, np.float32))
    return sc


def save_two_host_pod(tmp_path, with_journals=False):
    d = str(tmp_path / 'ckpts')
    mgrs = [PodCheckpointManager(d, rank=r, num_hosts=2, run_id='run-1',
                                 commit_timeout_s=10,
                                 topology={'dp': 2, 'mp': 1})
            for r in range(2)]
    if with_journals:
        for r, m in enumerate(mgrs):
            class _TS(object):
                _journal_path = str(tmp_path / ('j%d.jsonl' % r))
                epoch = 1

                def journal_position(self):
                    return 42 + 10 * int(self._journal_path[-7])
            m.task_service = _TS()
    prog = FakeProgram()
    for r, m in enumerate(mgrs):
        m.save(prog, scope_for(r), 4)
    for m in mgrs:
        m.flush()
        m.close()
    return d


def test_shape_change_restore_assembles_and_reports(tmp_path):
    """A 1-host pod restores a 2-host checkpoint: global arrays
    reassemble from the cross-host shard manifests, the info reports
    the old topology and EVERY old host's task-journal position (the
    re-stride inputs)."""
    d = save_two_host_pod(tmp_path, with_journals=True)
    one = PodCheckpointManager(d, rank=0, num_hosts=1, run_id='run-2',
                               commit_timeout_s=10)
    sc = Scope()
    reset_reshard_stats()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        info = one.restore(scope=sc)
    assert any('topology-change restore' in str(x.message) for x in w)
    assert info['step'] == 4
    assert info['pod_num_hosts'] == 2 and info['resharded'] is True
    np.testing.assert_array_equal(np.asarray(sc.get('w')), FULL_W)
    np.testing.assert_array_equal(
        np.asarray(sc.get('b')), np.full((3,), 1.5, np.float32))
    tjs = info['task_journals']
    assert sorted(tjs) == [0, 1]
    assert tjs[0]['position'] == 42 and tjs[1]['position'] == 52
    # without a program/mesh no resharding program runs — the executor
    # reshards at first dispatch
    assert reshard_stats['programs'] == 0
    one.close()


def test_same_shape_restore_stays_on_bit_exact_fast_path(tmp_path):
    """REGRESSION PIN (ISSUE 14 satellite): same-shape restore takes
    today's path — zero resharding programs, byte-identical params —
    so topology-change resume can never tax the common case."""
    d = save_two_host_pod(tmp_path)
    reset_reshard_stats()
    for r in range(2):
        m = PodCheckpointManager(d, rank=r, num_hosts=2, run_id='run-2',
                                 commit_timeout_s=10)
        sc = Scope()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter('always')
            info = m.restore(scope=sc)
        assert not any('topology-change' in str(x.message) for x in w)
        assert info['resharded'] is False and info['pod_num_hosts'] == 2
        got = np.asarray(sc.get('w'))
        assert isinstance(got, np.ndarray)
        assert got.tobytes() == FULL_W.tobytes()      # BYTE-identical
        m.close()
    assert reshard_stats['programs'] == 0
    assert reshard_stats['arrays'] == 0


def test_shape_change_restore_reshards_onto_real_mesh(tmp_path):
    """With a program + mesh, the restore places the assembled state on
    the NEW mesh through the resharding program (counted), values
    intact."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel import shard_parameter
    from paddle_tpu.parallel.mesh import make_mesh
    d = save_two_host_pod(tmp_path)
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        w = fluid.layers.create_parameter([4, 4], 'float32', name='w')
    shard_parameter(main_p.global_block().var('w'), ('dp', None))
    mesh = make_mesh(num_devices=2, axes={'dp': 2})
    one = PodCheckpointManager(d, rank=0, num_hosts=1, run_id='run-2',
                               commit_timeout_s=10)
    sc = Scope()
    reset_reshard_stats()
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        info = one.restore(program=main_p, scope=sc, mesh=mesh)
    assert info['resharded'] is True
    assert reshard_stats['programs'] == 1
    assert info['reshard']['arrays'] == 1
    got = sc.get('w')
    import jax
    assert isinstance(got, jax.Array)
    assert dict(got.sharding.mesh.shape) == {'dp': 2}
    np.testing.assert_array_equal(np.asarray(got), FULL_W)
    one.close()


def test_shape_change_restore_impossible_reshard_is_loud(tmp_path):
    """The ISSUE-14 satellite: an axis that does not divide the new
    mesh raises the actionable ReshardError instead of a bare XLA shape
    error at first dispatch."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel import shard_parameter
    from paddle_tpu.parallel.mesh import make_mesh
    d = save_two_host_pod(tmp_path)
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        fluid.layers.create_parameter([4, 4], 'float32', name='w')
    shard_parameter(main_p.global_block().var('w'), ('dp', None))
    mesh = make_mesh(num_devices=3, axes={'dp': 3})
    one = PodCheckpointManager(d, rank=0, num_hosts=3, run_id='run-2',
                               commit_timeout_s=10)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        with pytest.raises(ReshardError) as e:
            one.restore(program=main_p, scope=Scope(), mesh=mesh)
    assert "'w'" in str(e.value)
    assert '2-host checkpoint onto 3 host' in str(e.value)
    one.close()


def test_retention_protects_old_topology_checkpoints(tmp_path):
    """REGRESSION PIN: after a resize, committed OLD-topology
    checkpoints are restorable by the elastic restore() and must count
    toward — and be protected by — the keep budget, not evicted as dead
    partials on the first new-topology commit."""
    from paddle_tpu.core.checkpoint import list_checkpoints
    d = save_two_host_pod(tmp_path)              # 2-host committed ckpt-4
    one = PodCheckpointManager(d, rank=0, num_hosts=1, run_id='run-2',
                               commit_timeout_s=10, keep_last_n=3)
    sc = Scope()
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        info = one.restore(scope=sc)
    assert info['pod_num_hosts'] == 2
    prog = FakeProgram(names=('b',))
    sc2 = Scope()
    sc2.set('b', np.arange(3, dtype=np.float32))
    one.save(prog, sc2, 8)                       # first 1-host commit
    one.flush()
    steps = [s for s, _ in list_checkpoints(d)]
    assert steps == [4, 8], steps                # old-shape ckpt-4 kept
    pod_verify(os.path.join(d, 'ckpt-4'), None)  # still restorable
    # and a re-save at the OLD committed step must keep the committed
    # old-shape checkpoint (same history), not rewrite it in place
    one.save(prog, sc2, 4)
    one.flush()
    pod, _m = pod_verify(os.path.join(d, 'ckpt-4'), None)
    assert int(pod['num_hosts']) == 2            # untouched
    one.close()


def test_same_host_count_mesh_axes_change_engages_reshard(tmp_path):
    """dp=2,mp=1 -> dp=1,mp=2 at the SAME host count is still a
    topology change: the fast path would skip the divisibility gate."""
    d = save_two_host_pod(tmp_path)     # topology '2h x dp=2,mp=1'
    m = PodCheckpointManager(d, rank=0, num_hosts=2, run_id='run-2',
                             commit_timeout_s=10,
                             topology={'dp': 1, 'mp': 2})
    sc = Scope()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        info = m.restore(scope=sc)
    assert info['resharded'] is True
    assert any('topology-change restore' in str(x.message) for x in w)
    np.testing.assert_array_equal(np.asarray(sc.get('w')), FULL_W)
    m.close()
    # a manager that did NOT record axes cannot judge an axes change:
    # host-count comparison only, today's bit-exact fast path
    m2 = PodCheckpointManager(d, rank=1, num_hosts=2, run_id='run-3',
                              commit_timeout_s=10)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        info = m2.restore(scope=Scope())
    assert info['resharded'] is False
    assert not any('topology-change' in str(x.message) for x in w)
    m2.close()


def test_pod_verify_still_strict_and_commit_consistent(tmp_path):
    d = save_two_host_pod(tmp_path)
    path = os.path.join(d, 'ckpt-4')
    with pytest.raises(ValueError, match='pod shape changed'):
        pod_verify(path, num_hosts=4)
    pod, manifests = pod_verify(path, num_hosts=2)
    assert pod['topology'] == '2h x dp=2,mp=1'
    # a POD_COMMIT whose host list disagrees with num_hosts is corrupt
    pc = os.path.join(path, 'POD_COMMIT.json')
    rec = json.load(open(pc))
    rec['num_hosts'] = 3
    open(pc, 'w').write(json.dumps(rec))
    with pytest.raises(ValueError, match='inconsistent|pod shape'):
        pod_verify(path)


def test_heartbeat_payload_carries_topology(tmp_path):
    mgr = PodCheckpointManager(str(tmp_path / 'ck'), rank=0, num_hosts=2,
                               run_id='r1', heartbeat_interval_s=0.05,
                               topology={'dp': 2, 'mp': 2})
    try:
        deadline = time.time() + 5
        beats = {}
        while time.time() < deadline:
            beats = read_heartbeats(mgr.dirname, 2)
            if beats:
                break
            time.sleep(0.02)
        assert beats[0]['topology'] == '2h x dp=2,mp=2'
        from paddle_tpu import profiler
        import io
        import contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            profiler.pod_report()
        text = buf.getvalue()
        assert 'topology' in text and '2h x dp=2,mp=2' in text
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# the real thing: 2-host composed-mesh run, killed at a committed
# boundary, resumed on ONE host (shrink) with the journal re-strided
# ---------------------------------------------------------------------------
def test_resize_2_hosts_to_1_parity_and_exactly_once(tmp_path):
    chaos = _chaos()
    work = str(tmp_path)
    cache = os.path.join(work, 'compile-cache')
    data = os.path.join(work, 'data.rio')
    r = subprocess.run([sys.executable, chaos.ELASTIC_WORKER,
                        '--make-data', data, '64'], capture_output=True,
                       text=True, cwd=REPO, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    dataset = [l.strip() for l in open(data + '.hashes') if l.strip()]
    outs = lambda tag, n: [os.path.join(work, '%s-r%d.txt' % (tag, i))  # noqa: E731,E501
                           for i in range(n)]

    # uninterrupted 2-host reference
    res = chaos.run_pod(os.path.join(work, 'ref-ck'), outs('ref', 2),
                        total=8, every=2, cache_dir=cache, timeout=280,
                        worker=chaos.ELASTIC_WORKER, data_file=data)
    assert all(rc == 0 for rc, _ in res), \
        '\n'.join(e[-1500:] for _, e in res)
    refs = [chaos.read_elastic_out(p) for p in outs('ref', 2)]
    assert refs[0]['losses'] == refs[1]['losses']
    assert len(refs[0]['losses']) == 8

    failures = []

    def fail(msg):
        failures.append(msg)
        return 1

    _err, ref_recs = chaos.merge_pod_recs(refs, fail)
    assert not failures, failures

    # kill the 2-host pod at the committed step-4 boundary
    ckpt = os.path.join(work, 'ck')
    res = chaos.run_pod(ckpt, outs('kill', 2), total=8, every=2,
                        kill_rank=1, kill_at=4, cache_dir=cache,
                        timeout=280, worker=chaos.ELASTIC_WORKER,
                        data_file=data)
    assert res[1][0] == -signal.SIGKILL
    assert not any('WEDGED' in err for _, err in res)
    killed = [chaos.read_elastic_out(p) for p in outs('kill', 2)]

    # resume on ONE host: reshard + journal re-stride engage
    res = chaos.run_pod(ckpt, outs('fin', 1), total=8, every=2,
                        cache_dir=cache, timeout=280,
                        worker=chaos.ELASTIC_WORKER, data_file=data)
    assert all(rc == 0 for rc, _ in res), \
        '\n'.join(e[-1500:] for _, e in res)
    fin = chaos.read_elastic_out(outs('fin', 1)[0])
    resume = fin['resume']
    assert resume and resume % 2 == 0 and resume <= 4, fin
    assert fin['topo'] == (2, 1)
    assert fin['reshard'][0] >= 1, 'resharding path did not engage'
    assert fin['restride'] is not None

    err = chaos.check_resize_round(
        refs[0]['losses'], ref_recs, killed, [fin], resume, 8, dataset,
        fail, 'resize-2to1')
    assert err is None and not failures, failures
