"""Control flow + tensor arrays: While → lax.while_loop, StaticRNN /
DynamicRNN → lax.scan, IfElse/Switch dense selects, array ops.

Reference coverage model: unittests/test_while_op.py,
test_dynrnn_static_input.py, test_dyn_rnn.py, test_array_read_write_op.py,
test_lod_rank_table.py, test_switch.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.lod import create_lod_array


def _run(fetch, feed=None, startup=True):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetch)


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

def test_while_counter_sum():
    """sum 0..9 with a While loop over scalar carries."""
    layers = fluid.layers
    i = layers.fill_constant(shape=[1], dtype='int64', value=0)
    limit = layers.fill_constant(shape=[1], dtype='int64', value=10)
    total = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(x=i, y=limit)
    w = layers.While(cond=cond)
    with w.block():
        casted = layers.cast(i, 'float32')
        layers.assign(layers.elementwise_add(total, casted), total)
        layers.increment(i, value=1, in_place=True)
        layers.less_than(x=i, y=limit, cond=cond)
    out, = _run([total], startup=False)
    assert out[0] == pytest.approx(45.0)


def test_while_with_tensor_array():
    """Decode-style loop: write i^2 vectors into a TensorArray, stack."""
    layers = fluid.layers
    i = layers.fill_constant(shape=[1], dtype='int64', value=0)
    limit = layers.fill_constant(shape=[1], dtype='int64', value=5)
    x = layers.fill_constant(shape=[3], dtype='float32', value=1.0)
    arr = layers.array_write(x, i)  # initial write sizes the buffer
    layers.increment(i, value=1, in_place=True)
    cond = layers.less_than(x=i, y=limit)
    w = layers.While(cond=cond)
    with w.block():
        prev = layers.array_read(arr, layers.elementwise_sub(
            i, layers.fill_constant([1], 'int64', 1)))
        nxt = layers.scale(prev, scale=2.0)
        layers.array_write(nxt, i, array=arr)
        layers.increment(i, value=1, in_place=True)
        layers.less_than(x=i, y=limit, cond=cond)
    length = layers.array_length(arr)
    last = layers.array_read(arr, layers.elementwise_sub(
        i, layers.fill_constant([1], 'int64', 1)))
    ln, last_v = _run([length, last], startup=False)
    assert ln[0] == 5
    np.testing.assert_allclose(last_v, np.full(3, 16.0), rtol=1e-6)


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------

def test_static_rnn_matches_numpy():
    """h_t = tanh(x_t W + h_{t-1} U + b) against a numpy loop."""
    layers = fluid.layers
    T, B, D, H = 4, 3, 5, 6
    x = layers.data(name='x', shape=[T, B, D], dtype='float32',
                    append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(shape=[H], batch_ref=xt, init_value=0.0,
                       ref_batch_dim_idx=0)
        nh = layers.fc(input=[xt, h], size=H, act='tanh',
                       bias_attr=fluid.ParamAttr(
                           initializer=fluid.initializer.Constant(0.1)))
        rnn.update_memory(h, nh)
        rnn.output(nh)
    out = rnn()
    assert out.shape[0] == T

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.random.RandomState(0).randn(T, B, D).astype(np.float32)
    y, = exe.run(feed={'x': xs}, fetch_list=[out])
    assert y.shape == (T, B, H)

    # pull the fc weights to replay in numpy
    scope = fluid.global_scope()
    params = [n for n in scope.local_var_names() if 'w_' in n or '.b_' in n]
    ws = sorted(n for n in params if 'w_' in n)
    bs = [n for n in params if '.b_' in n]
    w0 = np.asarray(scope.get(ws[0]))
    w1 = np.asarray(scope.get(ws[1]))
    b = np.asarray(scope.get(bs[0]))
    h = np.zeros((B, H), np.float32)
    for t in range(T):
        h = np.tanh(xs[t] @ w0 + h @ w1 + b)
    np.testing.assert_allclose(y[-1], h, rtol=1e-4, atol=1e-5)


def test_static_rnn_trains():
    """Gradients flow through the scan: loss decreases."""
    layers = fluid.layers
    T, B, D, H = 5, 8, 4, 8
    x = layers.data(name='x', shape=[T, B, D], dtype='float32',
                    append_batch_size=False)
    target = layers.data(name='t', shape=[B, 1], dtype='float32',
                         append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(shape=[H], batch_ref=xt, ref_batch_dim_idx=0)
        nh = layers.fc(input=[xt, h], size=H, act='tanh')
        rnn.update_memory(h, nh)
        rnn.output(nh)
    seq = rnn()
    last = layers.slice(seq, axes=[0], starts=[T - 1], ends=[T])
    last = layers.reshape(last, [B, H])
    pred = layers.fc(input=last, size=1)
    loss = layers.mean(layers.square_error_cost(input=pred, label=target))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    xs = rng.randn(T, B, D).astype(np.float32)
    ts = rng.randn(B, 1).astype(np.float32)
    losses = [float(exe.run(feed={'x': xs, 't': ts},
                            fetch_list=[loss])[0][0]) for _ in range(30)]
    assert losses[-1] < 0.3 * losses[0], losses[::6]


# ---------------------------------------------------------------------------
# DynamicRNN
# ---------------------------------------------------------------------------

def _lod_batch(rng, lens, dim):
    data = rng.randn(sum(lens), dim).astype(np.float32)
    return create_lod_array(data, recursive_seq_lens=[list(lens)])


def test_dynamic_rnn_shapes_and_mask():
    layers = fluid.layers
    D, H = 4, 6
    x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        word = drnn.step_input(x)
        prev = drnn.memory(shape=[H], value=0.0)
        h = layers.fc(input=[word, prev], size=H, act='tanh')
        drnn.update_memory(prev, h)
        drnn.output(h)
    out = drnn()
    pooled = layers.sequence_last_step(out)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    lens = [3, 1, 4]
    feed_x = _lod_batch(rng, lens, D)
    y, p = exe.run(feed={'x': feed_x}, fetch_list=[out, pooled])
    assert y.shape == (sum(lens), H)
    assert p.shape == (len(lens), H)


def test_dynamic_rnn_trains_sequence_classifier():
    """NMT-style milestone: DynamicRNN encoder trains end-to-end on LoD."""
    layers = fluid.layers
    V, E, H = 30, 8, 16
    words = layers.data(name='w', shape=[1], dtype='int64', lod_level=1)
    label = layers.data(name='y', shape=[1], dtype='int64')
    emb = layers.embedding(input=words, size=[V, E])
    drnn = layers.DynamicRNN()
    with drnn.block():
        wt = drnn.step_input(emb)
        prev = drnn.memory(shape=[H], value=0.0)
        h = layers.fc(input=[wt, prev], size=H, act='tanh')
        drnn.update_memory(prev, h)
        drnn.output(h)
    enc = layers.sequence_last_step(drnn())
    logits = layers.fc(input=enc, size=2)
    loss = layers.mean(layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    lens = [3, 5, 2, 4]
    # task: label = whether first word id is >= V//2 (learnable from data)
    ids = rng.randint(0, V, (sum(lens), 1)).astype(np.int64)
    firsts = np.add.accumulate([0] + lens[:-1])
    ys = (ids[firsts, 0] >= V // 2).astype(np.int64).reshape(-1, 1)
    feed_w = create_lod_array(ids, recursive_seq_lens=[lens])
    losses = [float(exe.run(feed={'w': feed_w, 'y': ys},
                            fetch_list=[loss])[0][0]) for _ in range(40)]
    assert losses[-1] < 0.5 * losses[0], losses[::8]


def test_dynamic_rnn_static_input():
    layers = fluid.layers
    D, H = 3, 4
    x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
    ctx_in = layers.data(name='c', shape=[H], dtype='float32')
    drnn = layers.DynamicRNN()
    with drnn.block():
        wt = drnn.step_input(x)
        cs = drnn.static_input(ctx_in)
        prev = drnn.memory(shape=[H], value=0.0)
        h = layers.fc(input=[wt, prev, cs], size=H, act='tanh')
        drnn.update_memory(prev, h)
        drnn.output(h)
    out = drnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(4)
    lens = [2, 3]
    y, = exe.run(feed={'x': _lod_batch(rng, lens, D),
                       'c': rng.randn(len(lens), H).astype(np.float32)},
                 fetch_list=[out])
    assert y.shape == (sum(lens), H)


# ---------------------------------------------------------------------------
# IfElse / Switch / conditional_block
# ---------------------------------------------------------------------------

def test_ifelse_rowwise():
    layers = fluid.layers
    x = layers.data(name='x', shape=[2], dtype='float32')
    zero = layers.fill_constant_batch_size_like(x, [-1, 1], 'float32', 0.0)
    first = layers.slice(x, axes=[1], starts=[0], ends=[1])
    cond = layers.less_than(x=first, y=zero)  # [N,1] bool: x[:,0] < 0
    ie = layers.IfElse(cond)
    with ie.true_block():
        ie.output(layers.scale(ie.input(x), scale=-1.0))
    with ie.false_block():
        ie.output(layers.scale(ie.input(x), scale=2.0))
    merged, = ie()
    xs = np.array([[-1.0, 3.0], [2.0, -5.0]], np.float32)
    out, = _run([merged], feed={'x': xs}, startup=False)
    np.testing.assert_allclose(out, np.array([[1.0, -3.0], [4.0, -10.0]]),
                               rtol=1e-6)


def test_switch_piecewise():
    layers = fluid.layers
    step = layers.fill_constant(shape=[1], dtype='float32', value=7.0)
    lr = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    b1 = layers.fill_constant(shape=[1], dtype='float32', value=5.0)
    b2 = layers.fill_constant(shape=[1], dtype='float32', value=10.0)
    sw = layers.Switch()
    with sw.case(layers.less_than(step, b1)):
        layers.assign(layers.fill_constant([1], 'float32', 0.1), lr)
    with sw.case(layers.less_than(step, b2)):
        layers.assign(layers.fill_constant([1], 'float32', 0.01), lr)
    with sw.default():
        layers.assign(layers.fill_constant([1], 'float32', 0.001), lr)
    out, = _run([lr], startup=False)
    assert out[0] == pytest.approx(0.01)


# ---------------------------------------------------------------------------
# rank table + array conversion round trip
# ---------------------------------------------------------------------------

def test_lod_tensor_array_round_trip():
    layers = fluid.layers
    D = 3
    x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
    table = layers.lod_rank_table(x)
    arr = layers.lod_tensor_to_array(x, table)
    back = layers.array_to_lod_tensor(arr, table)
    ml = layers.max_sequence_len(table)
    rng = np.random.RandomState(5)
    lens = [2, 4, 1]
    feed_x = _lod_batch(rng, lens, D)
    y, m = _run([back, ml], feed={'x': feed_x}, startup=False)
    np.testing.assert_allclose(y, np.asarray(feed_x.data), rtol=1e-6)
    assert m[0] == 4


def test_reorder_by_rank():
    layers = fluid.layers
    x = layers.data(name='x', shape=[1], dtype='float32', lod_level=1)
    table = layers.lod_rank_table(x)
    reordered = layers.reorder_lod_tensor_by_rank(x, table)
    lens = [1, 3, 2]
    data = np.arange(6, dtype=np.float32).reshape(6, 1)
    feed_x = create_lod_array(data, recursive_seq_lens=[lens])
    y, = _run([reordered], feed={'x': feed_x}, startup=False)
    # rank order: seq1 (len 3) rows 1..3, seq2 (len 2) rows 4..5, seq0 row 0
    np.testing.assert_allclose(
        y.reshape(-1), np.array([1, 2, 3, 4, 5, 0], np.float32))
