"""OCR CRNN+CTC end-to-end (BASELINE.md north star #4: "LoDTensor var-len
path — end-to-end training runs"): conv backbone -> im2sequence -> BiGRU
-> warpctc over variable-length LoD labels, with greedy decode + edit
distance riding the same program.

Mirrors the reference's ocr_recognition training loop shape; variable
batches reuse ONE compiled program via the traced-LoD machinery.
"""
import numpy as np

import paddle_tpu as fluid
from models.crnn import build_crnn_train

NUM_CLASSES = 10  # tiny alphabet keeps the test fast


def _batch(rng, bs, max_len=6):
    imgs = rng.randn(bs, 1, 32, 96).astype(np.float32)
    lens = rng.randint(1, max_len + 1, bs)
    toks = rng.randint(0, NUM_CLASSES, int(lens.sum())).astype(np.int32)
    return imgs, fluid.create_lod_tensor(toks.reshape(-1, 1),
                                         [list(lens)])


def test_crnn_ctc_trains_end_to_end():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        images, label, avg_cost, decoded, edit = build_crnn_train(
            num_classes=NUM_CLASSES, img_h=32, img_w=96, lr=1e-3,
            rnn_hidden=32)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    imgs, lbl = _batch(rng, 4)
    losses = []
    for _ in range(8):
        l, = exe.run(main, feed={'pixel': imgs, 'label': lbl},
                     fetch_list=[avg_cost])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses  # CTC loss falls on a fixed batch

    # var-len LoD path: a batch with different label lengths reuses the
    # same program; decode + edit distance fetch alongside the loss
    imgs2, lbl2 = _batch(rng, 4, max_len=4)
    l2, dec, ed = exe.run(
        main, feed={'pixel': imgs2, 'label': lbl2},
        fetch_list=[avg_cost, decoded, edit], return_numpy=False)
    assert np.isfinite(float(np.asarray(l2).reshape(-1)[0]))
    dec_np = np.asarray(dec.data if hasattr(dec, 'data') else dec)
    ed_np = np.asarray(ed.data if hasattr(ed, 'data') else ed)
    assert ed_np.shape[0] == 4          # one distance per sequence
    assert (ed_np >= 0).all()
    # decoded tokens are class ids or -1 padding
    assert ((dec_np == -1) | ((dec_np >= 0) & (dec_np < NUM_CLASSES))).all()
