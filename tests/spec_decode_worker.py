"""Subprocess worker for test_spec_decode.py and spec_decode_smoke.py:
one SPECULATIVE decode-serving replica "cold start". Loads a
continuous-decode artifact that carries a verify program by FILE PATH
(the framework must never load into a serving process), attaches the
n-gram drafter, decodes a fixed set of self-repetitive prompts, and
prints transcripts, speculative stats, and the number of XLA backend
compiles as a JSON line:

    python spec_decode_worker.py ARTIFACT_DIR SEED N_PROMPTS MAX_NEW

With AOT sidecars present (export_decode default / cache_ctl prewarm
covering the decode_verify/ program), compiles must be 0 — the ISSUE 17
warm fresh-process acceptance bar.
"""
import json
import os
import sys


def main():
    artifact, seed, n, max_new = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]))
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('PTPU_PLATFORM', 'cpu')
    import numpy as np
    from jax import monitoring

    compiles = [0]

    def _listener(event, secs, **kw):
        if event == '/jax/core/compile/backend_compile_duration':
            compiles[0] += 1

    monitoring.register_event_duration_secs_listener(_listener)

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(here), 'paddle_tpu',
                                    'inference'))
    import decoding

    with decoding.DecodingPredictor(artifact, draft='ngram') as pred:
        vocab = pred._vocab
        big = max(pred.prompt_buckets or [8])
        rng = np.random.RandomState(seed)
        # self-repetitive prompts so the n-gram drafter actually fires
        # (verify dispatches happen regardless of acceptance)
        prompts = []
        for _ in range(n):
            pat = rng.randint(2, vocab, 2)
            plen = int(rng.randint(4, big + 1))
            prompts.append(np.tile(pat, plen)[:plen])
        streams = [pred.submit(p, max_new_tokens=max_new) for p in prompts]
        out = [s.result(120) for s in streams]
        snap = pred.stats.snapshot()
    assert 'paddle_tpu' not in sys.modules, \
        'the framework leaked into the serving process'
    print('SPEC %s' % json.dumps({
        'compiles': compiles[0], 'greedy': out,
        'verify_steps': snap['verify_steps'], 'drafted': snap['drafted'],
        'accepted': snap['accepted'], 'acc_rate': snap['acc_rate'],
        'tokens_per_dispatch': snap['tokens_per_dispatch'],
        'tokens': snap['tokens']}))
    print('SPEC_OK')


if __name__ == '__main__':
    main()
