"""Sparse/CTR path tests: SelectedRows gradients, sparse optimizer updates,
nce, hsigmoid, and mesh-sharded embeddings.

Methodology mirrors the reference's sparse op tests
(test_lookup_table_op.py sparse grad checks, test_nce.py, test_hsigmoid_op.py)
plus the dist-lookup-table parity idea: the sparse path must train
IDENTICALLY to the dense path — sparsity is an execution detail, not a
semantic one.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _train_embedding(is_sparse, optimizer, steps=5, seed=11):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=is_sparse)
        emb = fluid.layers.reshape(emb, shape=[-1, 32])
        pred = fluid.layers.fc(emb, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        optimizer().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            feed = {'ids': rng.randint(0, 50, (16, 4)),
                    'y': rng.randn(16, 1).astype(np.float32)}
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(l[0]))
        w = np.asarray(scope.get([v.name for v in main_p.all_parameters()
                                  if 'emb' in v.name or 'w' in v.name][0]))
    return losses, w


@pytest.mark.parametrize('opt_name,make_opt,tol', [
    # sgd/adagrad: untouched rows see zero grad in BOTH paths -> exact parity
    ('sgd', lambda: fluid.optimizer.SGD(learning_rate=0.1), 1e-5),
    ('adagrad', lambda: fluid.optimizer.Adagrad(learning_rate=0.1), 1e-5),
    # adam default (lazy_mode=False) densifies sparse grads -> exact parity
    ('adam', lambda: fluid.optimizer.Adam(learning_rate=0.05), 1e-5),
    # momentum / lazy adam are LAZY sparse (ref SparseMomentumFunctor /
    # SparseAdamFunctor lazy branch): untouched rows' velocity/moments
    # don't decay, so trajectories drift slightly from dense — bound it
    ('momentum', lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                                  momentum=0.9), 5e-2),
    ('adam_lazy', lambda: fluid.optimizer.Adam(learning_rate=0.05,
                                               lazy_mode=True), 5e-2),
])
def test_sparse_grad_matches_dense(opt_name, make_opt, tol):
    """is_sparse=True must train like dense: exactly for sgd/adagrad,
    within lazy-semantics drift for momentum/adam."""
    dense_losses, dense_w = _train_embedding(False, make_opt)
    sparse_losses, sparse_w = _train_embedding(True, make_opt)
    np.testing.assert_allclose(dense_losses, sparse_losses, rtol=tol,
                               atol=tol)
    w_tol = 1e-4 if tol < 1e-3 else 0.1
    np.testing.assert_allclose(dense_w, sparse_w, rtol=w_tol, atol=w_tol)


def test_selected_rows_merge_and_to_dense():
    import jax.numpy as jnp
    from paddle_tpu.core.selected_rows import SelectedRowsVal
    sr = SelectedRowsVal(jnp.asarray([3, 1, 3, 0], jnp.int32),
                         jnp.asarray([[1., 1.], [2., 2.], [3., 3.],
                                      [4., 4.]]), height=5)
    dense = np.asarray(sr.to_dense())
    assert dense[3].tolist() == [4., 4.]  # 1+3 accumulated
    assert dense[1].tolist() == [2., 2.]
    assert dense[4].tolist() == [0., 0.]
    m = sr.merged()
    md = np.asarray(m.to_dense())
    np.testing.assert_allclose(md, dense)
    # merged parks duplicates at row == height
    assert int(np.asarray(m.rows).max()) == 5


def test_nce_sparse_matches_dense_training():
    def build(is_sparse, seed=13):
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = seed
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
            cost = fluid.layers.nce(input=x, label=lab, num_total_classes=30,
                                    num_neg_samples=5, is_sparse=is_sparse)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        rng = np.random.RandomState(2)
        with fluid.scope_guard(scope):
            exe.run(startup_p)
            losses = []
            for _ in range(6):
                feed = {'x': rng.randn(32, 8).astype(np.float32),
                        'lab': rng.randint(0, 30, (32, 1))}
                l, = exe.run(main_p, feed=feed, fetch_list=[loss])
                losses.append(float(l[0]))
        return losses

    dense = build(False)
    sparse = build(True)
    np.testing.assert_allclose(dense, sparse, rtol=1e-5, atol=1e-5)
    assert dense[-1] < dense[0]  # converges


def test_nce_cost_value():
    """Forward cost matches the NCE formula computed in numpy with the same
    sampled ids (read back from SampleLabels)."""
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        cost = fluid.layers.nce(input=x, label=lab, num_total_classes=12,
                                num_neg_samples=4, bias_attr=False)
    block = main_p.global_block()
    op = next(o for o in block.ops if o.type == 'nce')
    w_name = op.inputs['Weight'][0]
    slab_name = op.outputs['SampleLabels'][0]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        xs = np.random.RandomState(5).randn(3, 6).astype(np.float32)
        labs = np.array([[1], [7], [11]])
        c, slab = exe.run(main_p, feed={'x': xs, 'lab': labs},
                          fetch_list=[cost, slab_name])
        w = np.asarray(scope.get(w_name))
    S, C = 4, 12
    logits = np.einsum('bkd,bd->bk', w[slab], xs)
    l = logits - np.log(S * (1.0 / C))
    is_true = np.zeros_like(l, dtype=bool)
    is_true[:, 0] = True
    sp = np.logaddexp(0, np.where(is_true, -l, l))
    np.testing.assert_allclose(c.reshape(-1), sp.sum(1), rtol=1e-5, atol=1e-5)


def test_hsigmoid_value_and_convergence():
    C = 10
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 3
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        cost = fluid.layers.hsigmoid(input=x, label=lab, num_classes=C)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)
    block = main_p.global_block()
    op = next(o for o in block.ops if o.type == 'hierarchical_sigmoid')
    w_name, b_name = op.inputs['W'][0], op.inputs['Bias'][0]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(1)
    xs = rng.randn(64, 8).astype(np.float32)
    labs = rng.randint(0, C, (64, 1))
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        # snapshot params BEFORE the first run: fetching `cost` from the
        # train program also executes its optimizer ops
        w = np.asarray(scope.get(w_name))
        b = np.asarray(scope.get(b_name)).reshape(-1)
        c0, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                      fetch_list=[cost])
        losses = []
        for _ in range(25):
            l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                         fetch_list=[loss])
            losses.append(float(l[0]))

    # numpy reference of the SimpleCode path BCE (matrix_bit_code.h)
    def ref_cost(x_, c_):
        code = c_ + C
        L = int(np.floor(np.log2(code)))
        tot = 0.0
        for j in range(L):
            idx = (code >> (j + 1)) - 1
            bit = (code >> j) & 1
            pre = np.clip(w[idx] @ x_ + b[idx], -40, 40)
            tot += np.logaddexp(0, pre) - bit * pre
        return tot

    want = np.array([ref_cost(xs[i], int(labs[i, 0])) for i in range(64)])
    np.testing.assert_allclose(c0.reshape(-1), want, rtol=2e-5, atol=2e-5)
    assert losses[-1] < losses[0] * 0.7  # learns


def test_sharded_embedding_parity():
    """Dist-lookup-table equivalent: embedding table sharded over the model
    axis of an 8-device mesh trains to the same losses as unsharded
    (ref parameter_prefetch.cc all-to-all semantics, subsumed by GSPMD)."""
    from paddle_tpu.parallel import shard_parameter
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.compiler import CompiledProgram

    def run(shard):
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = 21
        with fluid.program_guard(main_p, startup_p):
            ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            emb = fluid.layers.embedding(ids, size=[64, 16])
            emb_w = main_p.all_parameters()[0]
            emb_flat = fluid.layers.reshape(emb, shape=[-1, 64])
            pred = fluid.layers.fc(emb_flat, size=1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        if shard:
            shard_parameter(emb_w, ('mp', None))  # rows over model axis
        scope = fluid.core.Scope()
        rng = np.random.RandomState(9)
        feeds = [{'ids': rng.randint(0, 64, (16, 4)),
                  'y': rng.randn(16, 1).astype(np.float32)}
                 for _ in range(4)]
        losses = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup_p)
            prog = main_p
            if shard:
                mesh = make_mesh(axes={'dp': 4, 'mp': 2})
                prog = CompiledProgram(main_p).with_data_parallel(
                    loss_name=loss.name, mesh=mesh)
            for f in feeds:
                l, = exe.run(prog, feed=f, fetch_list=[loss.name])
                losses.append(float(np.asarray(l).reshape(-1)[0]))
        return losses

    base = run(False)
    sharded = run(True)
    np.testing.assert_allclose(base, sharded, rtol=2e-5, atol=2e-5)


def test_nce_custom_dist_sampler():
    """sampler='custom_dist' (ref math/sampler.cc CustomSampler): the
    CDF-searchsorted draw follows the supplied distribution, and an nce
    net trains with it."""
    from paddle_tpu.ops.sparse_ops import _sample_ids
    import jax

    probs = [0.7, 0.1, 0.1, 0.05, 0.05]
    ids = np.asarray(_sample_ids(jax.random.key(0), 2, (20000,), 5,
                                 probs))
    freq = np.bincount(ids, minlength=5) / 20000.0
    np.testing.assert_allclose(freq, probs, atol=0.02)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='int64')
        emb = fluid.layers.fc(x, size=16)
        cost = fluid.layers.nce(input=emb, label=y, num_total_classes=20,
                                num_neg_samples=5, sampler='custom_dist',
                                custom_dist=[1.0 / 20] * 10
                                + [0.05] * 10)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(32, 8).astype(np.float32),
            'y': rng.randint(0, 20, (32, 1)).astype(np.int64)}
    ls = [float(np.asarray(exe.run(main, feed=feed,
                                   fetch_list=[loss])[0]).reshape(-1)[0])
          for _ in range(12)]
    assert np.isfinite(ls).all()
    assert ls[-1] < ls[0]

    with pytest.raises(ValueError, match='custom_dist'):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x2 = fluid.layers.data('x2', shape=[8], dtype='float32')
            y2 = fluid.layers.data('y2', shape=[1], dtype='int64')
            fluid.layers.nce(input=x2, label=y2, num_total_classes=20,
                             sampler='custom_dist')


def test_hsigmoid_custom_tree_matches_default():
    """A custom tree that encodes the SAME complete binary tree must
    reproduce default-mode losses exactly (ref CustomCode vs SimpleCode,
    math/matrix_bit_code.h): path_table rows + path_code bits computed
    host-side, -1 padding."""
    C, D, B = 12, 6, 8
    rng = np.random.RandomState(3)
    xs = rng.randn(B, D).astype(np.float32)
    labels = rng.randint(0, C, (B, 1)).astype(np.int64)

    # SimpleCode in numpy: leaf->root node rows + bits, -1 padded
    Lmax = int(np.floor(np.log2(2 * C - 1)))
    table = -np.ones((B, Lmax), np.int64)
    codes = np.zeros((B, Lmax), np.int64)
    for i, c in enumerate(labels[:, 0]):
        code = int(c) + C
        length = int(np.floor(np.log2(code)))
        for j in range(length):
            table[i, j] = (code >> (j + 1)) - 1
            codes[i, j] = (code >> j) & 1

    def run(custom):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[D], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='int64')
            feed = {'x': xs, 'y': labels}
            if custom:
                pt = fluid.layers.data('pt', shape=[Lmax], dtype='int64')
                pc = fluid.layers.data('pc', shape=[Lmax], dtype='int64')
                out = fluid.layers.hsigmoid(
                    input=x, label=y, num_classes=C - 1,  # non-leaf count
                    path_table=pt, path_code=pc, is_custom=True)
                feed['pt'], feed['pc'] = table, codes
            else:
                out = fluid.layers.hsigmoid(input=x, label=y,
                                            num_classes=C)
            loss = fluid.layers.mean(out)
            fluid.optimizer.SGD(0.1).minimize(loss)
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            # identical weights for both modes
            for p in main.global_block().all_parameters():
                shape = tuple(p.shape)
                wr = np.random.RandomState(hash(shape) % 1000)
                scope.set(p.name, wr.randn(*shape).astype(np.float32)
                          * 0.1)
            return [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss])[0])
                .reshape(-1)[0]) for _ in range(4)]

    np.testing.assert_allclose(run(False), run(True), rtol=1e-5,
                               atol=1e-6)
