"""Transformer-base model (models/transformer.py) — build + convergence.

The reference's equivalent coverage is test_parallel_executor_transformer.py
(train steps must run and losses stay finite/decreasing).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid


def _train_tiny(bf16):
    from models.transformer import build_transformer_train
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 5
    with fluid.program_guard(main_p, startup_p):
        feeds, loss, fpt = build_transformer_train(
            src_vocab=300, trg_vocab=300, max_len=12, d_model=32, d_ff=64,
            n_head=2, n_layer=1, dropout=0.0, lr=0.002)
    assert fpt > 0
    if bf16:
        fluid.contrib.mixed_precision.enable_bf16(main_p)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    feed = {'src_ids': rng.randint(1, 300, (8, 12)),
            'trg_ids': rng.randint(1, 300, (8, 12)),
            'lbl_ids': rng.randint(1, 300, (8, 12))}
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(12):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(l[0]))
    return losses


@pytest.mark.parametrize('bf16', [False, True])
def test_tiny_transformer_trains(bf16):
    losses = _train_tiny(bf16)
    assert np.isfinite(losses).all()
    # memorizing a fixed batch: loss must drop well below ln(300) ~ 5.7
    assert losses[-1] < losses[0] - 0.5
